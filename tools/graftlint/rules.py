"""graftlint rules GL000-GL005: the JAX footguns that burn TPU runs.

evosax (arXiv:2212.04180) and EvoX (arXiv:2301.12457) both identify
tensorized purity and stable compilation caches as the load-bearing
invariants of GPU/TPU-native EC.  Each rule below turns one class of
violation into a machine-checked finding:

* **GL000** — bare ``assert`` in library code (vanishes under ``python -O``);
  the PR 1 assert lint folded in behind its existing baseline.
* **GL001** — PRNG key reuse: a key consumed by ``jax.random.*``/``split``
  (or passed into a helper) and then consumed again without re-splitting,
  including consumed keys stored back into a returned ``State`` (explicitly
  via ``replace(key=...)``/``State(key=...)`` or implicitly by returning a
  state whose key leaf was consumed and never replaced).
* **GL002** — host sync inside compiled paths: ``.item()``/``.tolist()``/
  ``np.asarray``/``float()``/``int()``/``bool()`` on traced values inside
  ``step``-family methods and functions reachable from them.
* **GL003** — Python ``if``/``while`` on traced values where
  ``jax.lax.cond``/``lax.while_loop``/``jnp.where`` is required.
* **GL004** — recompile hazards: ``jnp.array`` built from non-constant
  Python lists, Python ``for`` loops iterating traced arrays (silent
  unrolling), f-strings derived from traced values or array shapes.
* **GL005** — impure compiled methods: assignment to ``self.*`` inside the
  ``step`` family (components must stay static under jit; evolving values
  belong in the ``State``).
* **GL006** — topology-dependent PRNG folding: a value derived from
  ``jax.lax.axis_index`` feeding ``jax.random.fold_in``.  Folding the mesh
  position into a replicated key ties every random draw to *which shard
  evaluated it*: the same seed yields different trajectories on an 8-way vs
  a 4-way mesh, and elastic (re-meshed) checkpoint resume silently forks.
  Fold the **global slot index** instead (``parallel/sharded_problem.py``
  is the pragma'd sanctioned pattern).
* **GL007** — process-identity branching in compiled scope:
  ``jax.process_index()``/``jax.process_count()`` are *host* values that
  differ per process, so a Python ``if``/``while`` on them inside a jitted
  step traces a **different program on each host** of a ``jax.distributed``
  fleet — mismatched collectives, fleet-wide deadlock, no exception
  anywhere.  Host-side process branching (the single-writer checkpoint
  gating at segment boundaries, process-keyed fault schedules inside
  ``io_callback`` hooks) is the sanctioned pattern and is out of compiled
  scope by construction.
* **GL008** — numerics discipline in compiled scope: hard ``float64``
  references (TPUs have no native f64; XLA emulates it at a large
  compute+bytes cost), the implicit-promotion ``dtype=float`` builtin,
  and unannotated dtype-mixing — a state leaf ``.astype``-ed to a
  hard-coded float dtype outside the mixed-precision plane's one
  promote/demote seam (``StdWorkflow._step``; see
  ``evox_tpu.precision``).  Casting to an existing leaf's ``.dtype`` is
  policy-preserving and stays clean.

**Compiled scope.**  GL002-GL005 only apply inside functions that trace
under ``jax.jit``: methods/functions named ``step``/``init_step``/
``final_step``/``ask``/``tell``/``evaluate`` plus the monitor hook names,
and everything reachable from them through same-module calls (``self.x()``
and bare ``f()``).  Nested functions inherit the enclosing scope, except
functions handed to ``io_callback``/``pure_callback``/``jax.debug.callback``
— those run on the host by construction and are exempt.

**Loop-body scope.**  A function passed as the body of ``jax.lax.scan`` /
``fori_loop`` / ``while_loop`` traces into the compiled program once per
iteration *wherever* the combinator is called — including step/segment
builders outside the step family (``StdWorkflow._segment_program``, the
fused resilient segments).  Those bodies (plus their same-scope closure
through bare calls and ``lax.cond``/``lax.switch`` branch arguments) are
compiled scope too, and additionally **loop-body scope**: a host callback
(``io_callback``/``pure_callback``/``jax.debug.callback``) there fires once
per iteration and serializes the fused loop against the host, so GL002
flags the *call site itself* — exactly the stray-callback-in-the-scan-body
regression the fused segment work guards against.  Batch the data out as
scan outputs instead and flush at the segment boundary.

All checks are AST heuristics tuned for zero false positives on this
codebase; genuine-but-intentional sites carry a
``# graftlint: disable=GLxxx`` pragma with a justification comment, and
legacy debt rides the per-rule ratchet baselines (see ``engine.py``).
"""

from __future__ import annotations

import ast
import re
from typing import Any, Iterator

from .engine import Finding, Module, Rule

__all__ = ["RULES", "RULES_BY_CODE", "STEP_FAMILY"]

# Methods that trace under jax.jit: the Algorithm/Problem/Workflow step
# family plus the Monitor hooks StdWorkflow calls inside the jitted step.
STEP_FAMILY = frozenset(
    {
        "step",
        "init_step",
        "final_step",
        "ask",
        "tell",
        "evaluate",
        "post_ask",
        "pre_eval",
        "post_eval",
        "pre_tell",
        "record_nonfinite",
        "record_shard_quarantine",
        "record_auxiliary",
    }
)

# Functions whose first argument runs on the HOST, not in the trace.
_HOST_CALLBACK_FNS = frozenset(
    {"io_callback", "pure_callback", "callback", "debug_callback"}
)

# Attribute projections that are static (Python values) even on tracers.
# NOT `.at`: `x.at[i].set(v)` is the standard functional-update idiom and its
# result is every bit as traced as x.
_STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "sharding"})

# Calls that return static/host values even when handed traced arguments'
# static projections (dtype etc.).
_STATIC_CALLS = frozenset(
    {
        "len",
        "isinstance",
        "issubclass",
        "hasattr",
        "getattr",
        "callable",
        "type",
        "range",
        "finfo",
        "iinfo",
        "issubdtype",
        "result_type",
        "canonicalize_dtype",
        "comb",
        "tree_structure",
        "ndim",
    }
)

_KEY_NAME = re.compile(r"(^key$|_key$|^subkeys?$|^rng$|_rng$)")


def _terminates(block: list[ast.stmt]) -> bool:
    """Whether a block always leaves the enclosing FUNCTION (so its effects
    never reach any later code).  Return/Raise only: break/continue merely
    leave the loop — a key consumed before them is still consumed for the
    post-loop code and the next iteration."""
    return bool(block) and isinstance(block[-1], (ast.Return, ast.Raise))

# Calls a key may pass through without being consumed: derivation helpers,
# metadata queries, and host-side formatting (str(key) in an error message is
# not a draw).
_KEY_TRANSPARENT = frozenset(
    {
        "fold_in",
        "key_data",
        "wrap_key_data",
        "PRNGKey",
        "key",
        "clone",
        "issubdtype",
        "isinstance",
        "str",
        "repr",
        "format",
        "print",
        "len",
        "type",
        "hash",
        "id",
        "hasattr",
        "getattr",
    }
)

# A dotted "key" chain rooted at a module is API surface, not a key value
# (``jax.dtypes.prng_key``, ``jax.random.key``).
_MODULE_ROOTS = frozenset({"jax", "jnp", "np", "numpy", "lax", "random"})

_EXC_NAME = re.compile(r"(Error|Exception|Warning)$")


def _dotted(node: ast.AST) -> str | None:
    """``jax.random.split`` -> "jax.random.split"; None for non-name chains."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _key_expr_id(node: ast.AST) -> str | None:
    """Identity of a key-like expression: a Name matching the key pattern, or
    a short dotted chain ending in one (``state.key``)."""
    if isinstance(node, ast.Name) and _KEY_NAME.search(node.id):
        return node.id
    if isinstance(node, ast.Attribute) and _KEY_NAME.search(node.attr):
        root = _dotted(node)
        if root and root.count(".") <= 2 and root.split(".", 1)[0] not in _MODULE_ROOTS:
            return root
    return None


def _iter_functions(
    tree: ast.Module,
) -> Iterator[tuple[ast.FunctionDef | ast.AsyncFunctionDef, str | None, ast.AST | None]]:
    """Yield ``(func, class_name, enclosing_func)`` for every function."""

    def walk(node: ast.AST, cls: str | None, fn: ast.AST | None):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child, cls, fn
                yield from walk(child, cls, child)
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, child.name, None)
            else:
                yield from walk(child, cls, fn)

    yield from walk(tree, None, None)


def _body_walk(fn: ast.AST, *, into_nested: bool = False) -> Iterator[ast.AST]:
    """Walk a function body without descending into nested def/class scopes
    (unless ``into_nested``); lambdas are always descended (they inline into
    the trace)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            if not into_nested:
                continue
        stack.extend(ast.iter_child_nodes(node))


def _host_callback_names(fn: ast.AST) -> frozenset[str]:
    """Names of nested functions passed to io_callback/pure_callback/... —
    host-side by construction, exempt from compiled-scope rules."""
    names = set()
    for node in _body_walk(fn, into_nested=True):
        if isinstance(node, ast.Call):
            chain = _dotted(node.func) or ""
            if chain.rsplit(".", 1)[-1] in _HOST_CALLBACK_FNS and node.args:
                if isinstance(node.args[0], ast.Name):
                    names.add(node.args[0].id)
    return frozenset(names)


# Positional slot of the body function in each jax.lax loop combinator:
# lax.scan(body, ...), lax.fori_loop(lo, hi, body, ...),
# lax.while_loop(cond, body, ...).
_LOOP_BODY_ARG = {"scan": 0, "fori_loop": 2, "while_loop": 1}

# Branch-function slots of the non-loop structured-control combinators: a
# function handed to cond/switch from inside a loop body traces into the
# same per-iteration program, so the body closure follows them too.
_BRANCH_FN_CALLS = frozenset({"cond", "switch"})


def _loop_body_functions(mod: Module) -> dict[int, ast.AST]:
    """``{id(fn): fn}`` for every function that traces as the body of a
    ``lax.scan``/``fori_loop``/``while_loop`` anywhere in the module, plus
    the same-scope closure reached from those bodies through bare calls and
    ``lax.cond``/``lax.switch`` branch arguments.

    Resolution is lexical and follows Python's closure chain: a candidate
    name resolves to a ``def`` within the combinator call's enclosing
    function (any nesting depth), then within each transitively *enclosing*
    function (a sibling body defined one scope up is visible to the scan
    call — the nested-scan shape), then a module-level function, or — for
    ``self.m`` — a method of the enclosing class.  Lambdas inline into
    their enclosing scope and are not rooted here."""
    all_funcs = list(_iter_functions(mod.tree))
    module_funcs: dict[str, ast.AST] = {}
    class_methods: dict[tuple[str, str], ast.AST] = {}
    for fn, cls, enclosing in all_funcs:
        if enclosing is None and cls is None:
            module_funcs.setdefault(fn.name, fn)
        elif enclosing is None and cls is not None:
            class_methods[(cls, fn.name)] = fn
    fn_class = {id(fn): cls for fn, cls, _enc in all_funcs}
    enclosing_of = {id(fn): enc for fn, _cls, enc in all_funcs}

    def local_defs(owner: ast.AST) -> dict[str, ast.AST]:
        return {
            n.name: n
            for n in ast.walk(owner)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not owner
        }

    def resolve(node: ast.AST, owner: ast.AST) -> ast.AST | None:
        if isinstance(node, ast.Name):
            scope: ast.AST | None = owner
            while scope is not None:
                target = local_defs(scope).get(node.id)
                if target is not None:
                    return target
                scope = enclosing_of.get(id(scope))
            return module_funcs.get(node.id)
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            cls = fn_class.get(id(owner))
            if cls is not None:
                return class_methods.get((cls, node.attr))
        return None

    bodies: dict[int, ast.AST] = {}
    owners: dict[int, ast.AST] = {}  # body fn id -> enclosing-scope owner
    for fn, _cls, _enc in all_funcs:
        for node in _body_walk(fn, into_nested=False):
            if not isinstance(node, ast.Call):
                continue
            tail = (_dotted(node.func) or "").rsplit(".", 1)[-1]
            slot = _LOOP_BODY_ARG.get(tail)
            if slot is None or len(node.args) <= slot:
                continue
            target = resolve(node.args[slot], fn)
            if target is not None and id(target) not in bodies:
                bodies[id(target)] = target
                owners[id(target)] = fn

    # Same-scope closure: a body that dispatches to siblings through bare
    # calls or cond/switch branch arguments drags them into per-iteration
    # compiled scope (``body -> lax.cond(pred, frozen, step_out, ...)``).
    queue = list(bodies.values())
    while queue:
        body = queue.pop()
        owner = owners.get(id(body), body)
        for node in _body_walk(body, into_nested=True):
            if not isinstance(node, ast.Call):
                continue
            tail = (_dotted(node.func) or "").rsplit(".", 1)[-1]
            candidates: list[ast.AST] = []
            if isinstance(node.func, ast.Name):
                target = resolve(node.func, owner)
                if target is not None:
                    candidates.append(target)
            if tail in _BRANCH_FN_CALLS:
                for arg in node.args:
                    target = resolve(arg, owner)
                    if target is not None:
                        candidates.append(target)
            for target in candidates:
                if id(target) not in bodies:
                    bodies[id(target)] = target
                    owners[id(target)] = owner
                    queue.append(target)
    return bodies


def compiled_functions(mod: Module) -> list[ast.AST]:
    """Top-level (non-nested) functions that trace under jit: the step family
    plus same-module call-graph closure via ``self.m()`` / bare ``f()``."""
    all_funcs = list(_iter_functions(mod.tree))
    module_funcs: dict[str, list[ast.AST]] = {}
    class_methods: dict[tuple[str, str], ast.AST] = {}
    for fn, cls, enclosing in all_funcs:
        if enclosing is not None:
            continue  # nested defs handled inline by the body walkers
        if cls is None:
            module_funcs.setdefault(fn.name, []).append(fn)
        else:
            class_methods[(cls, fn.name)] = fn

    fn_class = {id(fn): cls for fn, cls, enc in all_funcs if enc is None}
    compiled: list[ast.AST] = []
    seen: set[int] = set()
    queue: list[ast.AST] = [
        fn for fn, cls, enc in all_funcs if enc is None and fn.name in STEP_FAMILY
    ]
    while queue:
        fn = queue.pop()
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        compiled.append(fn)
        cls = fn_class.get(id(fn))
        for node in _body_walk(fn, into_nested=True):
            if not isinstance(node, ast.Call):
                continue
            callee: list[ast.AST] = []
            if isinstance(node.func, ast.Name):
                callee = module_funcs.get(node.func.id, [])
            elif (
                isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"
                and cls is not None
            ):
                target = class_methods.get((cls, node.func.attr))
                callee = [target] if target is not None else []
            for c in callee:
                if c.name not in ("__init__", "setup") and id(c) not in seen:
                    queue.append(c)
    return compiled


# ---------------------------------------------------------------------------
# taint: which expressions are traced values inside a compiled function
# ---------------------------------------------------------------------------

_SEED_PARAM_NAMES = frozenset(
    {"state", "pop", "population", "fit", "fitness", "fitnesses", "key", "keys", "mask", "aux"}
)
_ARRAYISH_ANNOTATIONS = frozenset(
    {"State", "Array", "ndarray", "ArrayLike", "jax.Array", "jnp.ndarray"}
)
_CALLABLE_ANNOTATIONS = frozenset({"EvalFn", "Callable"})


class _Taint:
    """Statement-ordered taint tracking over one compiled function (nested
    non-host defs walked inline, sharing the environment — closures trace
    into the same program)."""

    def __init__(self, fn: ast.AST):
        self.tainted: set[str] = set()
        self.traced_callables: set[str] = set()
        # Per-field taint for dict literals with constant-string keys: a
        # carrier dict mixing traced leaves with host bookkeeping ints
        # (std_workflow's evaluate carrier) must not taint the host fields.
        self.dict_fields: dict[str, dict[str, bool]] = {}
        self._seed_params(fn)

    def _seed_params(self, fn: ast.AST) -> None:
        args = fn.args
        for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs) + (
            [args.vararg] if args.vararg else []
        ):
            ann = _dotted(a.annotation) if a.annotation is not None else None
            ann_tail = (ann or "").rsplit(".", 1)[-1]
            if ann in _ARRAYISH_ANNOTATIONS or ann_tail in _ARRAYISH_ANNOTATIONS:
                self.tainted.add(a.arg)
            elif ann in _CALLABLE_ANNOTATIONS or ann_tail in _CALLABLE_ANNOTATIONS:
                self.traced_callables.add(a.arg)
            elif ann is None and (
                a.arg in _SEED_PARAM_NAMES or _KEY_NAME.search(a.arg)
            ):
                self.tainted.add(a.arg)

    # -- expression query ---------------------------------------------------
    def is_traced(self, node: ast.AST | None) -> bool:
        if node is None:
            return False
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False  # x.shape / x.ndim / x.dtype are static
            return self.is_traced(node.value)
        if isinstance(node, ast.Call):
            chain = _dotted(node.func) or ""
            tail = chain.rsplit(".", 1)[-1]
            if tail in _STATIC_CALLS:
                return False
            if isinstance(node.func, ast.Name) and node.func.id in self.traced_callables:
                return True  # evaluate(pop) -> fitness array
            everything = list(node.args) + [k.value for k in node.keywords]
            if any(self.is_traced(a) for a in everything):
                return True
            return self.is_traced(node.func) if isinstance(node.func, ast.Attribute) else False
        if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
            return any(self.is_traced(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(self.is_traced(v) for v in node.values if v is not None)
        if isinstance(node, ast.Starred):
            return self.is_traced(node.value)
        if isinstance(node, ast.Subscript):
            if (
                isinstance(node.value, ast.Name)
                and node.value.id in self.dict_fields
                and isinstance(node.slice, ast.Constant)
                and isinstance(node.slice.value, str)
            ):
                fields = self.dict_fields[node.value.id]
                if node.slice.value in fields:
                    return fields[node.slice.value]
            return self.is_traced(node.value) or self.is_traced(node.slice)
        if isinstance(node, (ast.BinOp,)):
            return self.is_traced(node.left) or self.is_traced(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_traced(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.is_traced(v) for v in node.values)
        if isinstance(node, ast.Compare):
            # `"leaf" in state` / `x is None`: structural queries, static
            # under trace even on a traced container.
            if all(isinstance(op, (ast.In, ast.NotIn)) for op in node.ops) and isinstance(
                node.left, ast.Constant
            ):
                return False
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False
            return self.is_traced(node.left) or any(
                self.is_traced(c) for c in node.comparators
            )
        if isinstance(node, ast.IfExp):
            return self.is_traced(node.body) or self.is_traced(node.orelse)
        if isinstance(node, ast.JoinedStr):
            return any(
                self.is_traced(v.value)
                for v in node.values
                if isinstance(v, ast.FormattedValue)
            )
        if isinstance(node, ast.Slice):
            return any(self.is_traced(p) for p in (node.lower, node.upper, node.step))
        return False

    # -- statement-ordered propagation --------------------------------------
    def assign(self, target: ast.AST, traced: bool) -> None:
        if isinstance(target, ast.Name):
            (self.tainted.add if traced else self.tainted.discard)(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self.assign(e, traced)
        elif isinstance(target, ast.Starred):
            self.assign(target.value, traced)

    def _record_dict_literal(self, name: str, value: ast.Dict) -> bool:
        fields: dict[str, bool] = {}
        for k, v in zip(value.keys, value.values):
            if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
                return False  # dynamic keys: fall back to whole-name taint
            fields[k.value] = self.is_traced(v)
        self.dict_fields[name] = fields
        return True

    def visit_stmt(self, stmt: ast.AST) -> None:
        if isinstance(stmt, ast.Assign):
            if (
                len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, ast.Dict)
                and self._record_dict_literal(stmt.targets[0].id, stmt.value)
            ):
                fields = self.dict_fields[stmt.targets[0].id]
                self.assign(stmt.targets[0], any(fields.values()))
                return
            if (
                len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Subscript)
                and isinstance(stmt.targets[0].value, ast.Name)
                and stmt.targets[0].value.id in self.dict_fields
                and isinstance(stmt.targets[0].slice, ast.Constant)
                and isinstance(stmt.targets[0].slice.value, str)
            ):
                self.dict_fields[stmt.targets[0].value.id][
                    stmt.targets[0].slice.value
                ] = self.is_traced(stmt.value)
                return
            traced = self.is_traced(stmt.value)
            for t in stmt.targets:
                self.assign(t, traced)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self.assign(stmt.target, self.is_traced(stmt.value))
        elif isinstance(stmt, ast.AugAssign):
            if self.is_traced(stmt.value):
                self.assign(stmt.target, True)
        elif isinstance(stmt, ast.For):
            self.assign(stmt.target, self.is_traced(stmt.iter))
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if item.optional_vars is not None:
                    self.assign(
                        item.optional_vars, self.is_traced(item.context_expr)
                    )


def _seed_all_params(fn: ast.AST, taint: _Taint) -> None:
    """Taint every parameter of ``fn`` — the seeding for loop-body roots,
    whose arguments (scan carry/slice, fori index/value, while carry) are
    traced by construction regardless of their names."""
    args = fn.args
    for a in (
        list(args.posonlyargs)
        + list(args.args)
        + list(args.kwonlyargs)
        + ([args.vararg] if args.vararg else [])
    ):
        taint.tainted.add(a.arg)


def _compiled_statements(
    fn: ast.AST,
    host_names: frozenset[str],
    taint: _Taint,
    loop_ids: frozenset[int] = frozenset(),
    in_body: bool = False,
) -> Iterator[tuple[ast.AST, bool, "_Taint"]]:
    """Statement-ordered walk of a compiled function: propagates taint as it
    goes and yields ``(node, in_loop_body, taint_in_scope)``; nested defs
    walked inline unless they are host callbacks.  ``taint_in_scope`` is the
    environment the node must be judged against — a nested loop body gets a
    child taint with its own params seeded (scan carry/slice are traced by
    construction), so callers must use the YIELDED taint, not the root's.
    ``in_loop_body`` turns on inside functions registered as loop bodies
    (:func:`_loop_body_functions`) — per-iteration compiled scope."""

    def walk(node: ast.AST) -> Iterator[tuple[ast.AST, bool, "_Taint"]]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in host_names:
                return  # host callback: exempt
            inner = _Taint(node)
            if id(node) in loop_ids:
                # Every parameter of a loop body is traced by construction
                # (the carry/slice of scan, the index/value of fori_loop).
                _seed_all_params(node, inner)
            inner.tainted |= taint.tainted
            inner.traced_callables |= taint.traced_callables
            inner.dict_fields.update(taint.dict_fields)
            # The nested function traces into the same program; its findings
            # use the shared (approximate) environment.
            yield from _compiled_statements(
                node,
                host_names,
                inner,
                loop_ids,
                in_body or id(node) in loop_ids,
            )
            return
        if isinstance(node, ast.ClassDef):
            return
        if isinstance(node, ast.stmt):
            taint.visit_stmt(node)
        yield node, in_body, taint
        for child in ast.iter_child_nodes(node):
            yield from walk(child)

    for child in ast.iter_child_nodes(fn):
        yield from walk(child)


# ---------------------------------------------------------------------------
# GL000 — bare asserts (PR 1's assert lint, folded in)
# ---------------------------------------------------------------------------


class BareAssertRule(Rule):
    code = "GL000"
    title = "bare assert in library code"
    hint = (
        "asserts vanish under `python -O`; raise ValueError/TypeError with "
        "the offending values instead (see parallel/sharded_problem.py for "
        "the idiom)"
    )

    def check(self, mod: Module) -> list[Finding]:
        return [
            self.finding(
                mod,
                node,
                "bare `assert` in library code — validation must survive "
                "`python -O`",
            )
            for node in ast.walk(mod.tree)
            if isinstance(node, ast.Assert)
        ]


# ---------------------------------------------------------------------------
# GL001 — PRNG key reuse
# ---------------------------------------------------------------------------


class KeyReuseRule(Rule):
    code = "GL001"
    title = "PRNG key reuse"
    hint = (
        "split before reuse: `key, subkey = jax.random.split(key)` and give "
        "every consumer its own subkey; a state must carry a fresh key "
        "forward (`state.replace(key=new_key)`)"
    )

    # Mapping wrappers that replicate the mapped function per batch member:
    # a CLOSURE key consumed inside one is consumed once per instance.
    _MAP_WRAPPERS = frozenset({"vmap", "pmap"})

    def check(self, mod: Module) -> list[Finding]:
        findings: list[Finding] = []
        for fn, _cls, _enc in _iter_functions(mod.tree):
            findings.extend(self._check_function(mod, fn))
            findings.extend(self._check_mapped_closures(mod, fn))
        return findings

    # -- nested-workflow scope: keys closed over by vmapped functions --------
    def _check_mapped_closures(self, mod: Module, fn: ast.AST) -> list[Finding]:
        """The nested-workflow (HPO) reuse shape: a key from the OUTER
        scope consumed inside a ``jax.vmap``/``pmap``-mapped function.
        The mapped function runs once per batch member (inner instance),
        so a closure-captured key — unlike a mapped parameter — hands
        every instance the SAME stream: N inner workflows drawing
        identical randomness.  Split per instance, or fold in each
        instance's stable uid (``evox_tpu.hpo``'s identity-keyed
        contract)."""
        local_defs = {
            n.name: n
            for n in ast.walk(fn)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not fn
        }
        findings: list[Finding] = []
        flagged: set[tuple[int, str]] = set()
        for node in _body_walk(fn, into_nested=False):
            if not isinstance(node, ast.Call) or not isinstance(
                node.func, ast.Call
            ):
                continue
            wrapper = node.func
            tail = (_dotted(wrapper.func) or "").rsplit(".", 1)[-1]
            if tail not in self._MAP_WRAPPERS or not wrapper.args:
                continue
            mapped = wrapper.args[0]
            if isinstance(mapped, ast.Lambda):
                params = {a.arg for a in mapped.args.args}
                body_nodes = list(ast.walk(mapped.body))
            elif isinstance(mapped, ast.Name) and mapped.id in local_defs:
                target = local_defs[mapped.id]
                params = {a.arg for a in target.args.args}
                body_nodes = list(_body_walk(target, into_nested=True))
            else:
                continue  # attributes/externals: cannot see the body
            for n in body_nodes:
                if not isinstance(n, ast.Call):
                    continue
                ctail = (_dotted(n.func) or "").rsplit(".", 1)[-1]
                if (
                    ctail in _KEY_TRANSPARENT
                    or ctail in ("replace", "State")
                    or _EXC_NAME.search(ctail)
                ):
                    continue
                for arg in list(n.args) + [k.value for k in n.keywords]:
                    kid = _key_expr_id(arg)
                    if kid is None or kid.split(".", 1)[0] in params:
                        continue
                    if (n.lineno, kid) in flagged:
                        continue
                    flagged.add((n.lineno, kid))
                    findings.append(
                        self.finding(
                            mod,
                            n,
                            f"outer PRNG key `{kid}` consumed inside a "
                            f"`{tail}`-mapped function — every mapped "
                            f"instance draws IDENTICAL randomness; split "
                            f"the key per instance, or fold in each "
                            f"instance's stable uid",
                        )
                    )
        return findings

    # Consumption model: any call that receives a key-like expression uses it
    # up, except the key-transparent derivation calls (fold_in etc.) and the
    # store sites (State(...)/.replace(...)) — storing a FRESH key forward is
    # the contract, storing a CONSUMED key is the bug.
    def _check_function(self, mod: Module, fn: ast.AST) -> list[Finding]:
        findings: list[Finding] = []
        consumed: dict[str, int] = {}  # key id -> line of consuming use
        flagged: set[tuple[int, str]] = set()

        def flag(node: ast.AST, key_id: str, message: str) -> None:
            if (node.lineno, key_id) in flagged:
                return
            flagged.add((node.lineno, key_id))
            findings.append(self.finding(mod, node, message))

        def consume(node: ast.AST, key_id: str) -> None:
            if key_id in consumed:
                flag(
                    node,
                    key_id,
                    f"PRNG key `{key_id}` reused — already consumed on line "
                    f"{consumed[key_id]}; every use needs a fresh split",
                )
            else:
                consumed[key_id] = node.lineno

        def clear_root(name: str) -> None:
            consumed.pop(name, None)
            for k in [k for k in consumed if k.startswith(name + ".")]:
                consumed.pop(k)

        def handle_store(call: ast.Call) -> None:
            # State(key=...) / state.replace(key=...): storing a consumed key
            # back into a state leaf is deferred reuse.
            for kw in call.keywords:
                key_id = _key_expr_id(kw.value) if kw.value is not None else None
                if key_id and key_id in consumed:
                    flag(
                        call,
                        key_id,
                        f"consumed PRNG key `{key_id}` (used on line "
                        f"{consumed[key_id]}) stored back into the state — the "
                        "next step will draw the same randomness again",
                    )

        def handle_call(call: ast.Call) -> None:
            chain = _dotted(call.func) or ""
            tail = chain.rsplit(".", 1)[-1]
            if tail in _KEY_TRANSPARENT or _EXC_NAME.search(tail):
                return  # derivation/formatting/exception message: not a draw
            if tail == "replace" or tail == "State":
                handle_store(call)
                return
            for arg in list(call.args) + [k.value for k in call.keywords]:
                key_id = _key_expr_id(arg)
                if key_id is not None:
                    consume(arg, key_id)

        def visit_expr(node: ast.AST) -> None:
            # Innermost calls first: `split(key)` inside an assignment must
            # consume before the assignment target rebinds.
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)):
                    continue
                visit_expr(child)
            if isinstance(node, ast.Call):
                handle_call(node)

        def visit_block(stmts: list[ast.stmt]) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                if isinstance(stmt, ast.Assign):
                    visit_expr(stmt.value)
                    for t in stmt.targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                clear_root(n.id)
                elif isinstance(stmt, ast.AnnAssign):
                    if stmt.value is not None:
                        visit_expr(stmt.value)
                    if isinstance(stmt.target, ast.Name):
                        clear_root(stmt.target.id)
                elif isinstance(stmt, ast.AugAssign):
                    visit_expr(stmt.value)
                elif isinstance(stmt, ast.If):
                    visit_expr(stmt.test)
                    before = dict(consumed)
                    visit_block(stmt.body)
                    after_body = dict(consumed)
                    consumed.clear()
                    consumed.update(before)
                    visit_block(stmt.orelse)
                    # Union: consumed on either branch is consumed after —
                    # except a branch that terminates (return/raise/...)
                    # never reaches the fall-through code, so its
                    # consumptions do not carry over.
                    if not _terminates(stmt.body):
                        for k, v in after_body.items():
                            consumed.setdefault(k, v)
                    if _terminates(stmt.orelse):
                        consumed.clear()
                        consumed.update(
                            after_body if not _terminates(stmt.body) else before
                        )
                elif isinstance(stmt, (ast.For, ast.While)):
                    if isinstance(stmt, ast.For):
                        visit_expr(stmt.iter)
                    else:
                        visit_expr(stmt.test)
                    # Two passes over the body: a key consumed in iteration 1
                    # and not re-split is reused in iteration 2.  The loop
                    # target rebinds fresh each iteration, so it (and any
                    # dotted key rooted at it) clears before every pass.
                    for _pass in range(2):
                        if isinstance(stmt, ast.For):
                            for n in ast.walk(stmt.target):
                                if isinstance(n, ast.Name):
                                    clear_root(n.id)
                        visit_block(stmt.body)
                    visit_block(stmt.orelse)
                elif isinstance(stmt, ast.Return) and stmt.value is not None:
                    visit_expr(stmt.value)
                    self._check_return(mod, stmt, consumed, findings, flagged)
                elif isinstance(stmt, ast.Raise):
                    pass  # error messages mention keys without drawing from them
                elif isinstance(stmt, ast.Try):
                    visit_block(stmt.body)
                    for h in stmt.handlers:
                        visit_block(h.body)
                    visit_block(stmt.orelse)
                    visit_block(stmt.finalbody)
                elif isinstance(stmt, ast.With):
                    for item in stmt.items:
                        visit_expr(item.context_expr)
                    visit_block(stmt.body)
                else:
                    for child in ast.iter_child_nodes(stmt):
                        if isinstance(child, ast.expr):
                            visit_expr(child)

        visit_block([s for s in ast.iter_child_nodes(fn) if isinstance(s, ast.stmt)])
        return findings

    def _check_return(
        self,
        mod: Module,
        stmt: ast.Return,
        consumed: dict[str, int],
        findings: list[Finding],
        flagged: set[tuple[int, str]],
    ) -> None:
        """Returning a state whose stored key was consumed but never replaced
        (`return state.replace(fit=...)` after `jax.random.foo(state.key)`)
        hands the caller a state that will re-draw the same randomness."""
        value = stmt.value
        for key_id, line in list(consumed.items()):
            # rpartition: the LAST component is the key attribute (a deep id
            # like `self.state.key` replaces via `key=`, not `state.key=`).
            root, _, attr = key_id.rpartition(".")
            if not attr or not root:
                continue
            root_name = root.split(".", 1)[0]
            returns_root = any(
                isinstance(n, ast.Name) and n.id == root_name
                for n in ast.walk(value)
            )
            if not returns_root:
                continue
            # Either update idiom carries a fresh key forward:
            # `state.replace(key=...)` or a rebuilt `State(key=...)`.
            replaces_key = any(
                isinstance(n, ast.Call)
                and (
                    (isinstance(n.func, ast.Attribute) and n.func.attr == "replace")
                    or (_dotted(n.func) or "").rsplit(".", 1)[-1] == "State"
                )
                and any(kw.arg == attr for kw in n.keywords)
                for n in ast.walk(value)
            )
            if not replaces_key and (stmt.lineno, key_id) not in flagged:
                flagged.add((stmt.lineno, key_id))
                findings.append(
                    self.finding(
                        mod,
                        stmt,
                        f"`{key_id}` was consumed on line {line} but the "
                        f"returned state does not replace `{attr}` — the next "
                        "call will re-draw identical randomness",
                    )
                )


# ---------------------------------------------------------------------------
# GL002-GL005 — compiled-scope rules (share one taint walk)
# ---------------------------------------------------------------------------


class _CompiledScopeRule(Rule):
    """Base for rules that only fire inside jit-traced scope.

    The call-graph closure, host-callback analysis, raise/assert spans, and
    the statement-ordered taint walk are shared: the first compiled-scope
    rule to run performs ONE walk dispatching to every compiled-scope rule's
    ``check_node`` and caches the per-rule findings on the Module."""

    def check(self, mod: Module) -> list[Finding]:
        return list(_compiled_scope_findings(mod).get(self.code, []))

    def check_node(
        self,
        mod: Module,
        node: ast.AST,
        taint: _Taint,
        in_loop_body: bool = False,
    ) -> list[Finding]:
        raise NotImplementedError


def _compiled_scope_findings(mod: Module) -> dict[str, list[Finding]]:
    cached = getattr(mod, "_compiled_scope_findings", None)
    if cached is not None:
        return cached
    rules = [r for r in RULES if isinstance(r, _CompiledScopeRule)]
    findings: dict[str, list[Finding]] = {r.code: [] for r in rules}
    step_roots = compiled_functions(mod)
    loop_bodies = _loop_body_functions(mod)
    loop_ids = frozenset(loop_bodies)
    # Loop bodies lexically inside a step-family root are walked inline by
    # that root's pass; the rest (bodies in segment builders and other
    # non-step functions) become compiled roots of their own.
    covered: set[int] = set()
    for root in step_roots:
        covered.update(
            id(n)
            for n in ast.walk(root)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        )
    roots: list[tuple[ast.AST, bool]] = [(fn, False) for fn in step_roots]
    # A body lexically nested inside another body root (scan-in-scan with
    # the inner def inside the outer body) is walked inline by the outer
    # root's pass — rooting it separately would double every finding in it.
    body_roots = [fn for fid, fn in loop_bodies.items() if fid not in covered]
    nested_in_body: set[int] = set()
    for fn in body_roots:
        nested_in_body.update(
            id(n)
            for n in ast.walk(fn)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not fn
        )
    roots.extend(
        (fn, True) for fn in body_roots if id(fn) not in nested_in_body
    )
    for fn, fn_in_body in roots:
        host = _host_callback_names(fn)
        taint = _Taint(fn)
        if fn_in_body:
            _seed_all_params(fn, taint)
        # Code under `raise`/`assert` runs at most once, at trace time — an
        # f-string or float() in an error message is not a per-step hazard.
        error_spans = [
            (n.lineno, n.end_lineno or n.lineno)
            for n in _body_walk(fn, into_nested=True)
            if isinstance(n, (ast.Raise, ast.Assert))
        ]
        for node, in_body, scope_taint in _compiled_statements(
            fn, host, taint, loop_ids, fn_in_body
        ):
            for rule in rules:
                for f in rule.check_node(mod, node, scope_taint, in_body):
                    if not any(lo <= f.line <= hi for lo, hi in error_spans):
                        findings[rule.code].append(f)
    mod._compiled_scope_findings = findings
    return findings


class HostSyncRule(_CompiledScopeRule):
    code = "GL002"
    title = "host sync inside compiled path"
    hint = (
        "a device->host transfer blocks the TPU pipeline inside a jitted "
        "step; keep the value on-device (jnp ops) or move the host logic "
        "into io_callback/monitor accessors"
    )

    def check_node(
        self,
        mod: Module,
        node: ast.AST,
        taint: _Taint,
        in_loop_body: bool = False,
    ) -> list[Finding]:
        if not isinstance(node, ast.Call):
            return []
        out: list[Finding] = []
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in ("item", "tolist") and not node.args:
            receiver = func.value
            rooted_at_self = (
                isinstance(receiver, ast.Name) and receiver.id == "self"
            ) or (
                isinstance(receiver, ast.Attribute)
                and isinstance(receiver.value, ast.Name)
                and receiver.value.id == "self"
                and not taint.is_traced(receiver)
            )
            if not rooted_at_self:
                out.append(
                    self.finding(
                        mod,
                        node,
                        f"`.{func.attr}()` inside a compiled step forces a "
                        "blocking device->host sync per call",
                    )
                )
        chain = _dotted(func) or ""
        if chain in ("np.asarray", "np.array", "numpy.asarray", "numpy.array", "onp.asarray", "onp.array"):
            out.append(
                self.finding(
                    mod,
                    node,
                    f"`{chain}` inside a compiled step materializes on host "
                    "(ConcretizationError on traced values, silent constant "
                    "otherwise) — use jnp",
                )
            )
        if (
            isinstance(func, ast.Name)
            and func.id in ("float", "int", "bool")
            and len(node.args) == 1
            and taint.is_traced(node.args[0])
        ):
            out.append(
                self.finding(
                    mod,
                    node,
                    f"`{func.id}()` on a traced value inside a compiled step "
                    "— host sync (or trace-time ConcretizationError)",
                )
            )
        # Host callbacks are legitimate step-scope escapes (monitors stream
        # history through io_callback) — but inside a lax.scan/fori_loop
        # BODY they fire once per iteration and serialize the fused
        # multi-generation segment against the host, defeating the fusion.
        tail = chain.rsplit(".", 1)[-1]
        if in_loop_body and tail in _HOST_CALLBACK_FNS:
            out.append(
                self.finding(
                    mod,
                    node,
                    f"`{tail}` inside a lax.scan/fori_loop body — one host "
                    "round-trip per iteration serializes the fused segment; "
                    "batch the data out as scan outputs and flush it at the "
                    "segment boundary",
                    hint=(
                        "carry the payload out of the scan as a stacked "
                        "output (telemetry) and do the host work once per "
                        "segment — see StdWorkflow.run_segment / "
                        "Monitor._capture"
                    ),
                )
            )
        return out


class TracedBranchRule(_CompiledScopeRule):
    code = "GL003"
    title = "Python control flow on traced value"
    hint = (
        "Python `if`/`while` on a traced array re-traces per branch or "
        "crashes; use jnp.where for element selection, jax.lax.cond for "
        "branches, jax.lax.while_loop for loops"
    )

    def check_node(
        self,
        mod: Module,
        node: ast.AST,
        taint: _Taint,
        in_loop_body: bool = False,
    ) -> list[Finding]:
        if isinstance(node, (ast.If, ast.While)) and taint.is_traced(node.test):
            kw = "if" if isinstance(node, ast.If) else "while"
            return [
                self.finding(
                    mod,
                    node,
                    f"Python `{kw}` on a traced value inside a compiled step "
                    "— needs jax.lax.cond/while_loop/jnp.where",
                )
            ]
        return []


class RecompileHazardRule(_CompiledScopeRule):
    code = "GL004"
    title = "recompile hazard"
    hint = (
        "anything that varies call-to-call in Python (list contents, shapes "
        "formatted into strings, unrolled loops over arrays) changes the "
        "trace and recompiles; hoist constants to __init__, use lax.scan/"
        "fori_loop, and key caches by static config only"
    )

    def check_node(
        self,
        mod: Module,
        node: ast.AST,
        taint: _Taint,
        in_loop_body: bool = False,
    ) -> list[Finding]:
        out: list[Finding] = []
        if isinstance(node, ast.Call):
            chain = _dotted(node.func) or ""
            if chain.rsplit(".", 1)[-1] in ("array", "asarray") and (
                chain.startswith("jnp.") or chain.startswith("jax.numpy.")
            ):
                if node.args:
                    arg = node.args[0]
                    # Tracers in the list trace into the program exactly like
                    # jnp.stack — the hazard is non-constant HOST values,
                    # which bake into the trace and recompile when they vary.
                    host_elt = lambda e: not isinstance(e, ast.Constant) and not taint.is_traced(e)
                    literal_nonconst = isinstance(arg, (ast.List, ast.Tuple)) and any(
                        host_elt(e) for e in arg.elts
                    )
                    comp_nonconst = isinstance(
                        arg, (ast.ListComp, ast.GeneratorExp)
                    ) and host_elt(arg.elt)
                    if literal_nonconst or comp_nonconst:
                        out.append(
                            self.finding(
                                mod,
                                node,
                                f"`{chain}` built from a Python list inside a "
                                "compiled step — list contents become trace "
                                "constants (recompile when they change); use "
                                "jnp.stack on arrays or hoist to __init__",
                            )
                        )
        elif isinstance(node, ast.For):
            if taint.is_traced(node.iter):
                out.append(
                    self.finding(
                        mod,
                        node,
                        "Python `for` over a traced array inside a compiled "
                        "step — unrolls the trace (and recompiles when the "
                        "length changes); use jax.lax.scan/fori_loop",
                    )
                )
            elif (
                isinstance(node.iter, ast.Call)
                and isinstance(node.iter.func, ast.Name)
                and node.iter.func.id == "range"
                and any(taint.is_traced(a) for a in node.iter.args)
            ):
                out.append(
                    self.finding(
                        mod,
                        node,
                        "`range()` over a traced bound inside a compiled step "
                        "— use jax.lax.fori_loop",
                    )
                )
        elif isinstance(node, ast.JoinedStr):
            traced = taint.is_traced(node)
            shape_derived = any(
                isinstance(n, ast.Attribute) and n.attr == "shape"
                for v in node.values
                if isinstance(v, ast.FormattedValue)
                for n in ast.walk(v.value)
            )
            if traced or shape_derived:
                what = "a traced value" if traced else "an array shape"
                out.append(
                    self.finding(
                        mod,
                        node,
                        f"f-string built from {what} inside a compiled step — "
                        "shape/value-derived strings (e.g. dict cache keys) "
                        "silently fork the compile cache",
                    )
                )
        return out


class ImpureStepRule(_CompiledScopeRule):
    code = "GL005"
    title = "impure compiled method"
    hint = (
        "components are static under jit — a `self.*` write only happens at "
        "trace time and is frozen (or silently stale) afterwards; evolving "
        "values belong in the State (`state.replace(...)`)"
    )

    def check_node(
        self,
        mod: Module,
        node: ast.AST,
        taint: _Taint,
        in_loop_body: bool = False,
    ) -> list[Finding]:
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        out: list[Finding] = []
        for t in targets:
            elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for e in elts:
                if (
                    isinstance(e, ast.Attribute)
                    and isinstance(e.value, ast.Name)
                    and e.value.id == "self"
                ):
                    out.append(
                        self.finding(
                            mod,
                            node,
                            f"assignment to `self.{e.attr}` inside a compiled "
                            "step-family method — mutation only happens at "
                            "trace time, not per generation",
                        )
                    )
        return out


# ---------------------------------------------------------------------------
# GL006 — axis_index-derived PRNG folding (topology-dependent randomness)
# ---------------------------------------------------------------------------


class AxisIndexFoldRule(Rule):
    code = "GL006"
    title = "axis_index-derived PRNG folding"
    hint = (
        "folding the mesh position into a replicated key makes every random "
        "draw depend on the topology — the same seed diverges between an "
        "8-way and a 4-way mesh, and re-meshed checkpoint resume forks; "
        "fold the GLOBAL slot index of each individual instead "
        "(axis_index * local_n + arange(local_n), see "
        "parallel/sharded_problem.py)"
    )

    # Wrappers through which a nested function is invoked with positionally
    # mapped arguments (``jax.vmap(f)(xs)`` hands ``xs`` to ``f``'s params).
    _WRAPPERS = frozenset({"vmap", "pmap", "jit", "shard_map", "checkpoint"})
    # Wrappers whose mapped axis is a BATCH POSITION: an inline
    # ``jnp.arange``/``iota`` mapped through one of these is a lane index.
    _MAP_WRAPPERS = frozenset({"vmap", "pmap"})
    # Parameter names that declare a stable identity (the sanctioned thing
    # to fold): candidate uids, tenant identities.  A lane index renamed
    # `uid` is a lie the reviewer owns; the linter trusts the name, exactly
    # like the `_KEY_NAME` heuristic GL001 is built on.
    _UID_NAME = re.compile(r"(uid|candidate|identity|tenant)", re.IGNORECASE)

    def check(self, mod: Module) -> list[Finding]:
        # Cheap pre-filters: axis_index derivation (the original rule) or
        # the nested-workflow lane-index shape (an arange/iota mapped
        # through vmap into a fold_in).
        has_axis = "axis_index" in mod.source
        has_lane = (
            "fold_in" in mod.source
            and ("vmap" in mod.source or "pmap" in mod.source)
            and ("arange" in mod.source or "iota" in mod.source)
        )
        if not has_axis and not has_lane:
            return []
        findings: list[Finding] = []
        for fn, _cls, enclosing in _iter_functions(mod.tree):
            if enclosing is not None:
                continue  # nested defs analyzed inline with their parent
            findings.extend(self._check_tree(mod, fn))
        return findings

    def _call_target(self, call: ast.Call) -> tuple[Any, bool]:
        """``(target, mapped)`` — the function a call ultimately hands its
        args to (a bare ``f(...)`` name, or the Name/Lambda inside a
        wrapper application ``jax.vmap(f)(...)``) and whether the
        application maps a batch axis (vmap/pmap: positional args become
        per-batch-member parameter values)."""
        if isinstance(call.func, ast.Name):
            return call.func.id, False
        if isinstance(call.func, ast.Call):
            inner = call.func
            tail = (_dotted(inner.func) or "").rsplit(".", 1)[-1]
            if tail in self._WRAPPERS and inner.args:
                mapped = tail in self._MAP_WRAPPERS
                if isinstance(inner.args[0], ast.Name):
                    return inner.args[0].id, mapped
                if mapped and isinstance(inner.args[0], ast.Lambda):
                    return inner.args[0], True
        return None, False

    @staticmethod
    def _is_lane_index(node: ast.AST) -> bool:
        """An inline batch-position iota: ``jnp.arange(...)`` /
        ``lax.iota(...)`` handed straight to a vmap application — the
        lane-index idiom (contrast: a *stable-uid* array is state/config
        data, reaching the call as a name)."""
        return isinstance(node, ast.Call) and (
            (_dotted(node.func) or "").rsplit(".", 1)[-1]
            in ("arange", "iota")
        )

    def _check_tree(self, mod: Module, fn: ast.AST) -> list[Finding]:
        # Whole-lexical-tree fixpoint taint (statement order ignored — a
        # deliberate over-approximation; axis_index use is rare and the
        # pragma is the escape hatch for sanctioned sites).  Nested defs
        # share the environment, and calling a nested function — directly
        # or through jax.vmap — with a tainted argument taints the matching
        # parameter, so the shard-position value is tracked through the
        # per-individual vmap idiom.
        nested: dict[str, ast.AST] = {
            n.name: n
            for n in ast.walk(fn)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not fn
        }
        tainted: set[str] = set()

        def derived(node: ast.AST) -> bool:
            for n in ast.walk(node):
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(n, ast.Call):
                    tail = (_dotted(n.func) or "").rsplit(".", 1)[-1]
                    if tail == "axis_index":
                        return True
                if isinstance(n, ast.Name) and n.id in tainted:
                    return True
            return False

        changed = True
        while changed:
            changed = False
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and derived(node.value):
                    for t in node.targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name) and n.id not in tainted:
                                tainted.add(n.id)
                                changed = True
                elif (
                    isinstance(node, (ast.AugAssign, ast.AnnAssign))
                    and node.value is not None
                    and derived(node.value)
                    and isinstance(node.target, ast.Name)
                    and node.target.id not in tainted
                ):
                    tainted.add(node.target.id)
                    changed = True
                elif isinstance(node, ast.Call):
                    target, mapped = self._call_target(node)
                    params: list[str] = []
                    if isinstance(target, str) and target in nested:
                        params = [a.arg for a in nested[target].args.args]
                    elif isinstance(target, ast.Lambda):
                        params = [a.arg for a in target.args.args]
                    for param, arg in zip(params, node.args):
                        # A batch-position iota mapped through vmap/pmap is
                        # a LANE index: folding it (instead of a stable
                        # candidate uid) ties the stream to placement —
                        # the nested-workflow twin of the axis_index bug.
                        lane = (
                            mapped
                            and self._is_lane_index(arg)
                            and not self._UID_NAME.search(param)
                        )
                        if (derived(arg) or lane) and param not in tainted:
                            tainted.add(param)
                            changed = True

        findings: list[Finding] = []
        flagged: set[int] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if (_dotted(node.func) or "").rsplit(".", 1)[-1] != "fold_in":
                continue
            operands = list(node.args) + [k.value for k in node.keywords]
            if any(derived(a) for a in operands) and node.lineno not in flagged:
                flagged.add(node.lineno)
                findings.append(
                    self.finding(
                        mod,
                        node,
                        "`fold_in` fed a placement-derived value "
                        "(`axis_index` shard position, or a vmap lane "
                        "index) — the PRNG stream depends on WHERE the "
                        "value runs, so the same seed diverges across mesh "
                        "sizes / lane assignments and re-meshed or "
                        "re-packed resume forks; fold the global slot "
                        "index or the stable candidate uid instead",
                    )
                )
        return findings


# ---------------------------------------------------------------------------
# GL007 — process-identity branching in compiled scope (fleet divergence)
# ---------------------------------------------------------------------------


class ProcessBranchRule(Rule):
    code = "GL007"
    title = "process-identity branching in compiled scope"
    hint = (
        "jax.process_index()/process_count() are HOST values that differ "
        "per process: Python `if`/`while` on them inside a jitted step "
        "traces a DIFFERENT program on each host of a jax.distributed "
        "fleet, and the mismatched collectives deadlock the whole fleet; "
        "move the branch to host-side supervisor code (segment "
        "boundaries), or make the behavior data-dependent via a traced "
        "value every process computes identically"
    )

    def check(self, mod: Module) -> list[Finding]:
        if (
            "process_index" not in mod.source
            and "process_count" not in mod.source
        ):
            return []  # cheap pre-filter
        # Compiled scope = the step-family closure plus loop bodies rooted
        # outside it (the same scope GL002-GL005 analyze); host-callback
        # functions are exempt — process-keyed host behavior (single-writer
        # gating, fleet fault schedules) is exactly what belongs there.
        roots: list[ast.AST] = list(compiled_functions(mod))
        covered = {
            id(n)
            for r in roots
            for n in ast.walk(r)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        body_roots = [
            fn
            for fid, fn in _loop_body_functions(mod).items()
            if fid not in covered
        ]
        nested_in_body: set[int] = set()
        for fn in body_roots:
            nested_in_body.update(
                id(n)
                for n in ast.walk(fn)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n is not fn
            )
        roots.extend(fn for fn in body_roots if id(fn) not in nested_in_body)
        findings: list[Finding] = []
        for fn in roots:
            findings.extend(self._check_root(mod, fn))
        return findings

    @staticmethod
    def _is_process_call(node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        tail = (_dotted(node.func) or "").rsplit(".", 1)[-1]
        return tail in ("process_index", "process_count")

    def _check_root(self, mod: Module, fn: ast.AST) -> list[Finding]:
        host = _host_callback_names(fn)

        # Collect nodes lexically inside host-callback defs so both the
        # taint fixpoint and the branch scan skip them: process-keyed host
        # behavior (single-writer gating, fleet fault schedules) is exactly
        # what belongs in a host callback.
        host_nodes: set[int] = set()
        for n in ast.walk(fn):
            if (
                isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n.name in host
            ):
                host_nodes.update(id(x) for x in ast.walk(n))

        # GL006-style whole-tree fixpoint taint: names assigned from
        # process_index()/process_count()-derived expressions (statement
        # order ignored — a deliberate over-approximation; the pragma is
        # the escape hatch for sanctioned sites).
        tainted: set[str] = set()

        def derived(node: ast.AST) -> bool:
            for n in ast.walk(node):
                if id(n) in host_nodes:
                    continue
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if self._is_process_call(n):
                    return True
                if isinstance(n, ast.Name) and n.id in tainted:
                    return True
            return False

        changed = True
        while changed:
            changed = False
            for node in ast.walk(fn):
                if id(node) in host_nodes:
                    continue
                if isinstance(node, ast.Assign) and derived(node.value):
                    for t in node.targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name) and n.id not in tainted:
                                tainted.add(n.id)
                                changed = True
                elif (
                    isinstance(node, (ast.AugAssign, ast.AnnAssign))
                    and node.value is not None
                    and derived(node.value)
                    and isinstance(node.target, ast.Name)
                    and node.target.id not in tainted
                ):
                    tainted.add(node.target.id)
                    changed = True

        findings: list[Finding] = []
        flagged: set[int] = set()
        for node in ast.walk(fn):
            if id(node) in host_nodes:
                continue
            if not isinstance(node, (ast.If, ast.While, ast.IfExp)):
                continue
            if derived(node.test) and node.lineno not in flagged:
                flagged.add(node.lineno)
                kw = (
                    "if"
                    if isinstance(node, (ast.If, ast.IfExp))
                    else "while"
                )
                findings.append(
                    self.finding(
                        mod,
                        node,
                        f"Python `{kw}` on a `jax.process_index()`/"
                        f"`process_count()`-derived value inside compiled "
                        f"scope — each host of a fleet traces a different "
                        f"program and the mismatched collectives deadlock",
                    )
                )
        return findings


# ---------------------------------------------------------------------------
# GL008 — f64 / unannotated dtype-mixing in compiled scope (precision plane)
# ---------------------------------------------------------------------------


class DtypeDisciplineRule(Rule):
    code = "GL008"
    title = "f64 / unannotated dtype-mixing in compiled scope"
    hint = (
        "TPUs have no native float64 (XLA emulates it at a massive "
        "throughput cost) and the mixed-precision plane "
        "(evox_tpu.precision) owns every storage<->compute cast at ONE "
        "seam in StdWorkflow._step; a hard-coded f64 dtype — or an ad-hoc "
        "float `.astype` on a state leaf inside compiled scope — either "
        "silently multiplies the run's HBM bytes or silently moves a leaf "
        "across the precision boundary behind the policy's back.  Use the "
        "compute dtype, cast to an existing leaf's `.dtype` "
        "(policy-preserving), or route the cast through a PrecisionPolicy "
        "leaf map"
    )

    # Hard-coded float dtype tails: the rule only fires on LITERAL dtype
    # targets — `x.astype(other.dtype)` and variable dtypes are
    # policy-preserving/unknowable and stay clean.
    _F64_TAILS = frozenset({"float64", "double"})
    _FLOAT_TAILS = frozenset({"float64", "float32", "float16", "bfloat16"})
    # Names a compiled function's evolving-state parameter goes by (the
    # same convention the taint seeds use): `state.leaf.astype(...)` /
    # `state["leaf"].astype(...)` with one of these receivers is a state
    # leaf crossing a dtype boundary outside the policy seam.
    _STATE_NAMES = frozenset({"state", "carry", "st", "new_st", "algo_state"})

    def check(self, mod: Module) -> list[Finding]:
        src = mod.source
        # Cheap pre-filter: "float" (not "float64") so the implicit-f64
        # `dtype=float` builtin — a documented GL008 case — cannot slip
        # through a file that never spells the full dtype name.
        if (
            "float" not in src
            and "double" not in src
            and "astype" not in src
        ):
            return []  # cheap pre-filter
        # Compiled scope: the step-family closure plus loop-body roots
        # (the same scope GL007 analyzes); host-callback defs are exempt.
        roots: list[ast.AST] = list(compiled_functions(mod))
        covered = {
            id(n)
            for r in roots
            for n in ast.walk(r)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        body_roots = [
            fn
            for fid, fn in _loop_body_functions(mod).items()
            if fid not in covered
        ]
        nested_in_body: set[int] = set()
        for fn in body_roots:
            nested_in_body.update(
                id(n)
                for n in ast.walk(fn)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n is not fn
            )
        roots.extend(fn for fn in body_roots if id(fn) not in nested_in_body)
        findings: list[Finding] = []
        for fn in roots:
            findings.extend(self._check_root(mod, fn))
        return findings

    @classmethod
    def _dtype_tail(cls, node: ast.AST) -> str | None:
        """The literal dtype a node names, if any: a dotted attribute tail
        (``jnp.float64`` -> "float64"), a bare ``float64`` name, or a
        string constant ``"float64"``."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        tail = (_dotted(node) or "").rsplit(".", 1)[-1]
        return tail or None

    def _is_state_leaf(self, node: ast.AST) -> bool:
        """``state.leaf`` / ``state["leaf"]`` for a conventional state
        receiver name — the expressions whose dtype IS the storage policy."""
        if isinstance(node, (ast.Attribute, ast.Subscript)):
            base = node.value
            return isinstance(base, ast.Name) and base.id in self._STATE_NAMES
        return False

    def _check_root(self, mod: Module, fn: ast.AST) -> list[Finding]:
        host = _host_callback_names(fn)
        host_nodes: set[int] = set()
        for n in ast.walk(fn):
            if (
                isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n.name in host
            ):
                host_nodes.update(id(x) for x in ast.walk(n))
        # f64 references inside COMPARISONS are f64-AVOIDANCE guards
        # (`if x.dtype == jnp.float64: ...` — code upholding the rule's
        # intent), not f64 construction: exempt them from case (1).
        compare_nodes: set[int] = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Compare):
                compare_nodes.update(id(x) for x in ast.walk(n))

        findings: list[Finding] = []
        flagged: set[int] = set()
        for node in ast.walk(fn):
            if id(node) in host_nodes or not hasattr(node, "lineno"):
                continue
            if node.lineno in flagged:
                continue
            # (1) hard f64: a dotted `<numpy-ish>.float64`/`.double`
            # reference or a bare `float64` name (never a bare `double` —
            # that is an ordinary variable name), a "float64" dtype
            # string, or the implicit-promotion form `dtype=float` (the
            # Python builtin is f64 under x64).
            if (
                isinstance(node, (ast.Attribute, ast.Name))
                and id(node) not in compare_nodes
            ):
                if isinstance(node, ast.Name):
                    hit = node.id == "float64"
                else:
                    dotted = _dotted(node) or ""
                    head, _, tail = dotted.rpartition(".")
                    numpyish = head.rsplit(".", 1)[-1] in (
                        "np",
                        "jnp",
                        "numpy",
                        "jax",
                    )
                    hit = tail == "float64" or (
                        tail in self._F64_TAILS and numpyish
                    )
                if hit:
                    flagged.add(node.lineno)
                    findings.append(
                        self.finding(
                            mod,
                            node,
                            "float64 referenced in compiled scope — TPUs "
                            "have no native f64; XLA emulation multiplies "
                            "both compute and HBM bytes",
                        )
                    )
                    continue
            if isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg != "dtype" or id(kw.value) in host_nodes:
                        continue
                    tail = self._dtype_tail(kw.value)
                    implicit = (
                        isinstance(kw.value, ast.Name)
                        and kw.value.id == "float"
                    )
                    if (
                        tail in self._F64_TAILS or implicit
                    ) and node.lineno not in flagged:
                        flagged.add(node.lineno)
                        findings.append(
                            self.finding(
                                mod,
                                kw.value,
                                "dtype=float64 (or the implicit-f64 "
                                "`dtype=float` builtin) in compiled scope",
                            )
                        )
                # (2) unannotated dtype-mixing: a state leaf `.astype`-ed
                # to a hard-coded FLOAT dtype outside the policy seam.
                # Integer/bool casts (index math) and `.astype(x.dtype)`
                # (policy-preserving) stay clean.
                dtype_arg = None
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype"
                    and self._is_state_leaf(node.func.value)
                ):
                    # Positional or keyword spelling — `.astype(f32)` and
                    # `.astype(dtype=f32)` are the same crossing.
                    dtype_arg = node.args[0] if node.args else next(
                        (kw.value for kw in node.keywords if kw.arg == "dtype"),
                        None,
                    )
                if dtype_arg is not None:
                    tail = self._dtype_tail(dtype_arg)
                    # `.astype(float)` is the implicit-f64 builtin —
                    # the same promotion the dtype= keyword check flags.
                    implicit = (
                        isinstance(dtype_arg, ast.Name)
                        and dtype_arg.id == "float"
                    )
                    if (
                        tail in self._FLOAT_TAILS or implicit
                    ) and node.lineno not in flagged:
                        flagged.add(node.lineno)
                        findings.append(
                            self.finding(
                                mod,
                                node,
                                f"state leaf cast to a hard-coded float "
                                f"dtype ({tail or 'the implicit-f64 float builtin'}) "
                                f"inside compiled scope — "
                                f"an unannotated crossing of the storage/"
                                f"compute boundary the PrecisionPolicy "
                                f"seam owns",
                            )
                        )
        return findings


# Imported at module bottom: host_rules needs the helpers above, and the
# registry below needs HOST_RULES — the late import keeps one rule catalog
# without a cycle at import time.
from .host_rules import HOST_RULES  # noqa: E402

RULES: list[Rule] = [
    BareAssertRule(),
    KeyReuseRule(),
    HostSyncRule(),
    TracedBranchRule(),
    RecompileHazardRule(),
    ImpureStepRule(),
    AxisIndexFoldRule(),
    ProcessBranchRule(),
    DtypeDisciplineRule(),
    *HOST_RULES,
]
RULES_BY_CODE = {r.code: r for r in RULES}
